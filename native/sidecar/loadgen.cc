// loadgen — wrk2-style replayer for the ingress_plus_tpu serve loop.
//
// The reference measures its data plane with wrk2 replaying a labeled
// request corpus through nginx (SURVEY.md §4, BASELINE config #1).  This
// is that harness for our split architecture: it plays pre-encoded
// request frames (utils/export_corpus.py) over N unix-socket connections
// with a bounded in-flight window per connection, and reports throughput
// + latency percentiles + verdict counts as one JSON line.
//
// Single-threaded epoll (the build host has 1 core; the serve loop is the
// thing under test).  Build: make -C native/sidecar
//
// Usage: loadgen --socket /tmp/ipt.sock --corpus corpus.bin
//                [--connections 8] [--inflight 32] [--requests 10000]

#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <string>
#include <unordered_map>
#include <vector>

#include "protocol.hpp"

namespace {

uint64_t NowNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
}

struct Conn {
  int fd = -1;
  ipt::FrameReader reader;
  std::string outbuf;
  size_t out_off = 0;
  int inflight = 0;
};

struct Options {
  std::string socket_path = "/tmp/ingress_plus_tpu.sock";
  std::string corpus_path;
  int connections = 8;
  int inflight = 32;
  long total_requests = 10000;
};

std::vector<std::string> LoadCorpusFrames(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) { perror("corpus open"); exit(2); }
  std::string all;
  char buf[1 << 16];
  size_t n;
  while ((n = fread(buf, 1, sizeof buf, f)) > 0) all.append(buf, n);
  fclose(f);
  std::vector<std::string> frames;
  size_t off = 0;
  while (all.size() - off >= 8) {
    if (memcmp(all.data() + off, ipt::kReqMagic, 4) != 0) {
      fprintf(stderr, "corpus corrupt at %zu\n", off);
      exit(2);
    }
    uint32_t len;
    memcpy(&len, all.data() + off + 4, 4);
    if (all.size() - off < 8ull + len) break;
    frames.emplace_back(all.substr(off, 8ull + len));
    off += 8ull + len;
  }
  if (frames.empty()) { fprintf(stderr, "empty corpus\n"); exit(2); }
  return frames;
}

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) { fprintf(stderr, "missing value for %s\n", a.c_str()); exit(2); }
      return argv[++i];
    };
    if (a == "--socket") opt.socket_path = next();
    else if (a == "--corpus") opt.corpus_path = next();
    else if (a == "--connections") opt.connections = atoi(next());
    else if (a == "--inflight") opt.inflight = atoi(next());
    else if (a == "--requests") opt.total_requests = atol(next());
    else { fprintf(stderr, "unknown arg %s\n", a.c_str()); return 2; }
  }
  if (opt.corpus_path.empty()) { fprintf(stderr, "--corpus required\n"); return 2; }

  std::vector<std::string> corpus = LoadCorpusFrames(opt.corpus_path);

  int ep = epoll_create1(0);
  std::vector<Conn> conns(opt.connections);
  for (int c = 0; c < opt.connections; ++c) {
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, opt.socket_path.c_str(), sizeof(addr.sun_path) - 1);
    if (connect(fd, (sockaddr*)&addr, sizeof addr) != 0) {
      perror("connect"); return 3;
    }
    fcntl(fd, F_SETFL, O_NONBLOCK);
    conns[c].fd = fd;
    // EPOLLIN only: with a permanently-registered EPOLLOUT the wait loop
    // busy-spins at 100% CPU whenever the in-flight window is full (the
    // socket stays writable), starving the single-core serve loop under
    // test.  EPOLLOUT is toggled on only while outbuf has a backlog.
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = c;
    epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev);
  }

  std::vector<bool> want_out(opt.connections, false);
  auto set_events = [&](int c, bool out) {
    if (want_out[c] == out) return;
    want_out[c] = out;
    epoll_event ev{};
    ev.events = out ? (EPOLLIN | EPOLLOUT) : EPOLLIN;
    ev.data.u32 = uint32_t(c);
    epoll_ctl(ep, EPOLL_CTL_MOD, conns[c].fd, &ev);
  };

  std::unordered_map<uint64_t, uint64_t> sent_ns;
  sent_ns.reserve(opt.connections * opt.inflight * 2);
  std::vector<uint64_t> latencies_ns;
  latencies_ns.reserve(opt.total_requests);
  long sent = 0, received = 0;
  long attacks = 0, blocked = 0, fail_open = 0;
  uint64_t next_id = 1;
  uint64_t t_start = NowNs();

  auto pump_one = [&](int ci) {
    Conn& c = conns[ci];
    // enqueue new requests while under the in-flight window
    while (c.inflight < opt.inflight && sent < opt.total_requests) {
      std::string frame = corpus[sent % corpus.size()];
      uint64_t id = next_id++;
      memcpy(&frame[8], &id, 8);  // re-id: payload starts at offset 8
      sent_ns[id] = NowNs();
      c.outbuf += frame;
      ++c.inflight;
      ++sent;
    }
    // flush pending writes
    while (c.out_off < c.outbuf.size()) {
      ssize_t n = write(c.fd, c.outbuf.data() + c.out_off,
                        c.outbuf.size() - c.out_off);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        perror("write"); exit(4);
      }
      c.out_off += size_t(n);
    }
    if (c.out_off == c.outbuf.size()) { c.outbuf.clear(); c.out_off = 0; }
    set_events(ci, !c.outbuf.empty());
  };
  for (int c = 0; c < opt.connections; ++c) pump_one(c);

  epoll_event events[64];
  while (received < opt.total_requests) {
    int nev = epoll_wait(ep, events, 64, 1000);
    if (nev < 0) { if (errno == EINTR) continue; perror("epoll"); return 4; }
    if (nev == 0 && sent == received) continue;
    for (int i = 0; i < nev; ++i) {
      int ci = int(events[i].data.u32);
      Conn& c = conns[ci];
      if (events[i].events & EPOLLIN) {
        uint8_t buf[1 << 16];
        ssize_t n;
        while ((n = read(c.fd, buf, sizeof buf)) > 0) {
          c.reader.Feed(buf, size_t(n), [&](const uint8_t* p, size_t len) {
            ipt::Response r = ipt::DecodeResponse(p, len);
            auto it = sent_ns.find(r.req_id);
            if (it != sent_ns.end()) {
              latencies_ns.push_back(NowNs() - it->second);
              sent_ns.erase(it);
            }
            if (r.attack()) ++attacks;
            if (r.blocked()) ++blocked;
            if (r.fail_open()) ++fail_open;
            ++received;
            --c.inflight;
          });
        }
        if (n == 0) { fprintf(stderr, "server closed connection\n"); return 5; }
      }
      pump_one(ci);
    }
  }
  uint64_t t_end = NowNs();

  std::sort(latencies_ns.begin(), latencies_ns.end());
  auto pct = [&](double p) -> double {
    if (latencies_ns.empty()) return 0;
    size_t idx = size_t(p * (latencies_ns.size() - 1));
    return latencies_ns[idx] / 1e3;  // µs
  };
  double secs = (t_end - t_start) / 1e9;
  printf(
      "{\"requests\": %ld, \"seconds\": %.3f, \"rps\": %.1f, "
      "\"p50_us\": %.0f, \"p90_us\": %.0f, \"p99_us\": %.0f, "
      "\"p999_us\": %.0f, \"attacks\": %ld, \"blocked\": %ld, "
      "\"fail_open\": %ld}\n",
      received, secs, received / secs, pct(0.50), pct(0.90), pct(0.99),
      pct(0.999), attacks, blocked, fail_open);
  return 0;
}
