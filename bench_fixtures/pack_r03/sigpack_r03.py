"""Signature packs — the libproton proton.db analog.

The reference's libproton consumes a compiled attack-signature database
(proton.db, closed format, synced from the Wallarm cloud; SURVEY.md §2.2 /
§3.4).  Our open equivalent: keyword/template packs expanded into the same
``Rule`` objects the SecLang front-end produces, so one compiler back-end
serves both formats.

``generate_signature_rules`` deterministically expands the bundled packs to
the ~1.5k-rule scale of benchmark config #2/#3 (BASELINE.md) — realistic
rule-count pressure on the bitap tables without inventing artificial noise:
every generated rule is a plausible attack signature (keyword × context
template).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Sequence

from ingress_plus_tpu.compiler.seclang import Rule

# FIXTURE EDIT (round 5): the original line resolved to the live
# package rules dir; the frozen fixture must be self-contained, so
# RULES_DIR points at the adjacent frozen crs/ tree instead.
RULES_DIR = Path(__file__).resolve().parent

# (class, base_id, severity, targets, templates) — {w} is the keyword slot.
# Templates are regexes in our supported subset; authored for this project.
_PACK_TEMPLATES = [
    ("sqli", 942500, "ERROR", ["args", "body"], [
        r"(?i)\b{w}\s*\(",
        r"(?i)'\s*{w}",
        r"(?i){w}\s*\(\s*(?:select|0x|char)",
        r"(?i){w}\s+(?:from|into|table|database|where)\b",
        r"(?i)\b{w}\b\s*(?:--|#|/\*)",
    ]),
    ("rce", 932500, "ERROR", ["args", "body"], [
        r"(?i)(?:;|\||&|`|\$\()\s*{w}(?:\s|$|[;,&|)'\"`\x1f])",
        r"(?i)\b{w}\s+-[a-z]",
        r"(?i)\b{w}\s+/(?:etc|tmp|var|dev|proc)\b",
    ]),
    ("php", 933500, "WARNING", ["args", "body"], [
        r"(?i)\b{w}\s*\(",
        r"(?i){w}\s*\(\s*[\"'\$]",
    ]),
    ("xss", 941500, "ERROR", ["args", "body"], [
        r"(?i)<\s*{w}\b",
        r"(?i)\b{w}\s*=",
    ]),
    ("lfi", 930500, "ERROR", ["uri", "args", "body"], [
        r"(?i){w}",
        r"(?i)(?:\.\./|%2e%2e)[^\s]{0,40}{w}",
    ]),
    ("java", 944500, "ERROR", ["args", "body"], [
        r"(?i){w}",
        r"(?i){w}\s*[\.\(]",
    ]),
]

_PACK_KEYWORDS: Dict[str, List[str]] = {
    "sqli": [
        "union", "select", "insert", "update", "delete", "drop", "truncate",
        "exec", "execute", "declare", "fetch", "cursor", "having", "group by",
        "order by", "limit", "offset", "substring", "substr", "concat",
        "group_concat", "load_file", "outfile", "dumpfile", "benchmark",
        "sleep", "pg_sleep", "waitfor", "dbms_lock", "utl_http", "utl_inaddr",
        "extractvalue", "updatexml", "xmltype", "information_schema",
        "sqlite_master", "sysobjects", "syscolumns", "pg_catalog",
        "mysql\\.user", "xp_cmdshell", "xp_dirtree", "sp_executesql",
        "sp_oacreate", "openrowset", "openquery", "linked_server", "char",
        "nchar", "varchar", "cast", "convert", "coalesce", "nullif", "isnull",
        "version", "database", "current_user", "session_user", "system_user",
        "schema", "table_name", "column_name", "hex", "unhex", "to_base64",
        "from_base64", "randomblob", "sqlite_version", "pragma",
        "attach database", "json_extract", "regexp", "rlike", "soundex",
        "make_set", "elt", "procedure analyse",
    ],
    "rce": [
        "cat", "tac", "less", "more", "head", "tail", "nl", "od", "strings",
        "ls", "dir", "find", "locate", "which", "whereis", "id", "whoami",
        "uname", "hostname", "ifconfig", "ip addr", "netstat", "ss", "ps",
        "top", "env", "printenv", "set", "export", "wget", "curl", "fetch",
        "lynx", "nc", "ncat", "netcat", "socat", "telnet", "ssh", "scp",
        "rsync", "ftp", "tftp", "bash", "dash", "zsh", "ksh", "csh", "tcsh",
        "python", "python3", "perl", "ruby", "php", "node", "lua", "awk",
        "sed", "xargs", "tee", "chmod", "chown", "ln", "cp", "mv", "rm",
        "touch", "mkdir", "mkfifo", "mount", "umount", "crontab", "at",
        "systemctl", "service", "kill", "pkill", "nohup", "disown", "sudo",
        "su", "passwd", "useradd", "usermod", "groupadd", "visudo", "dd",
        "base64", "openssl", "gpg", "tar", "gzip", "bzip2", "xz", "zip",
        "unzip", "make", "gcc", "cc", "go run", "rustc",
    ],
    "php": [
        "eval", "assert", "system", "exec", "shell_exec", "passthru", "popen",
        "proc_open", "pcntl_exec", "call_user_func", "call_user_func_array",
        "create_function", "array_map", "array_filter", "array_walk",
        "register_shutdown_function", "register_tick_function", "ob_start",
        "extract", "parse_str", "putenv", "getenv", "ini_set", "ini_get",
        "dl", "symlink", "link", "readlink", "posix_kill", "posix_setuid",
        "posix_getpwuid", "apache_child_terminate", "apache_setenv",
        "highlight_file", "show_source", "php_uname", "phpversion",
        "phpinfo", "get_defined_vars", "get_defined_functions", "scandir",
        "opendir", "readdir", "glob", "file_get_contents",
        "file_put_contents", "fopen", "fwrite", "fputs", "readfile",
        "unlink", "rename", "copy", "tmpfile", "tempnam",
        "move_uploaded_file", "base64_decode", "gzinflate", "gzuncompress",
        "gzdecode", "str_rot13", "convert_uudecode", "hex2bin", "pack",
        "unserialize", "igbinary_unserialize", "yaml_parse", "simplexml_load_string",
    ],
    "xss": [
        "script", "iframe", "embed", "object", "applet", "meta", "base",
        "form", "svg", "math", "video", "audio", "img", "input", "body",
        "style", "link", "textarea", "button", "select", "option", "keygen",
        "marquee", "blink", "details", "dialog", "template", "slot",
        "onabort", "onactivate", "onafterprint", "onanimationend",
        "onanimationiteration", "onanimationstart", "onauxclick",
        "onbeforecopy", "onbeforecut", "onbeforeinput", "onbeforeprint",
        "onbeforeunload", "onblur", "oncanplay", "oncanplaythrough",
        "onchange", "onclick", "onclose", "oncontextmenu", "oncopy",
        "oncuechange", "oncut", "ondblclick", "ondrag", "ondragend",
        "ondragenter", "ondragleave", "ondragover", "ondragstart", "ondrop",
        "ondurationchange", "onemptied", "onended", "onerror", "onfocus",
        "onfocusin", "onfocusout", "onfullscreenchange", "ongotpointercapture",
        "onhashchange", "oninput", "oninvalid", "onkeydown", "onkeypress",
        "onkeyup", "onload", "onloadeddata", "onloadedmetadata", "onloadstart",
        "onlostpointercapture", "onmessage", "onmousedown", "onmouseenter",
        "onmouseleave", "onmousemove", "onmouseout", "onmouseover",
        "onmouseup", "onmousewheel", "onoffline", "ononline", "onpagehide",
        "onpageshow", "onpaste", "onpause", "onplay", "onplaying",
        "onpointercancel", "onpointerdown", "onpointerenter",
        "onpointerleave", "onpointermove", "onpointerout", "onpointerover",
        "onpointerup", "onpopstate", "onprogress", "onratechange", "onreset",
        "onresize", "onscroll", "onsearch", "onseeked", "onseeking",
        "onselect", "onselectionchange", "onselectstart", "onstalled",
        "onstorage", "onsubmit", "onsuspend", "ontimeupdate", "ontoggle",
        "ontouchcancel", "ontouchend", "ontouchmove", "ontouchstart",
        "ontransitionend", "onunload", "onvolumechange", "onwaiting",
        "onwheel",
    ],
    "lfi": [
        "etc/passwd", "etc/shadow", "etc/group", "etc/hosts", "etc/crontab",
        "etc/sudoers", "etc/fstab", "etc/issue", "etc/motd", "etc/mtab",
        "etc/resolv\\.conf", "etc/hostname", "etc/networks",
        "etc/ssh/sshd_config", "etc/ssh/ssh_config", "etc/mysql/my\\.cnf",
        "proc/self/environ", "proc/self/cmdline", "proc/self/maps",
        "proc/self/status", "proc/version", "proc/net/tcp", "proc/net/route",
        "var/log/auth\\.log", "var/log/secure", "var/log/messages",
        "var/log/syslog", "var/log/wtmp", "var/log/lastlog",
        "windows/win\\.ini", "windows/system\\.ini", "boot\\.ini",
        "windows/repair/sam", "windows/system32/config",
        "inetpub/wwwroot", "\\.aws/credentials", "\\.ssh/id_rsa",
        "\\.ssh/authorized_keys", "\\.git/config", "\\.svn/entries",
        "wp-config\\.php", "configuration\\.php", "localsettings\\.php",
        "config\\.inc\\.php", "settings\\.py", "database\\.yml",
        "secrets\\.yml", "appsettings\\.json", "web\\.config",
        "\\.env", "\\.htaccess", "\\.htpasswd", "\\.bash_history",
        "\\.mysql_history", "\\.viminfo",
    ],
    "java": [
        "java\\.lang\\.runtime", "java\\.lang\\.processbuilder",
        "java\\.lang\\.system", "java\\.lang\\.class",
        "java\\.io\\.objectinputstream", "java\\.rmi\\.server",
        "javax\\.naming\\.initialcontext", "javax\\.naming\\.spi",
        "javax\\.script\\.scriptenginemanager", "javax\\.el\\.elprocessor",
        "com\\.sun\\.rowset\\.jdbcrowsetimpl",
        "com\\.sun\\.org\\.apache\\.xalan",
        "org\\.apache\\.commons\\.collections",
        "org\\.apache\\.commons\\.beanutils",
        "org\\.apache\\.xalan\\.xsltc", "org\\.codehaus\\.groovy",
        "org\\.springframework\\.beans", "org\\.springframework\\.context",
        "org\\.hibernate\\.engine", "org\\.mozilla\\.javascript",
        "bsh\\.interpreter", "clojure\\.lang\\.compiler", "ysoserial",
        "marshalsec", "getruntime", "getdeclaredmethod", "getmethod",
        "newinstance", "defineclass", "urlclassloader", "scriptengine",
        "nashorn", "jexl", "mvel", "spel", "freemarker\\.template",
        "velocity\\.runtime",
    ],
}


def generate_signature_rules() -> List[Rule]:
    """Deterministically expand packs into Rules (keyword × template)."""
    rules: List[Rule] = []
    for cls, base_id, severity, targets, templates in _PACK_TEMPLATES:
        words = _PACK_KEYWORDS[cls]
        rid = base_id
        for t_idx, template in enumerate(templates):
            for w in words:
                pattern = template.replace("{w}", w)
                rules.append(Rule(
                    rule_id=rid,
                    operator="rx",
                    argument=pattern,
                    targets=list(targets),
                    transforms=["urlDecodeUni", "lowercase"],
                    action="block",
                    severity=severity,
                    msg="sigpack:%s template %d keyword %r" % (cls, t_idx, w),
                    tags=["attack-%s" % cls, "paranoia-level/2", "sigpack"],
                    paranoia=2,
                ))
                rid += 1
    return rules


def load_bundled_rules(include_sigpack: bool = True) -> List[Rule]:
    """Bundled CRS-shaped SecLang rules (+ signature packs) — the default
    full ruleset for benchmark config #2/#3."""
    from ingress_plus_tpu.compiler.seclang import load_seclang_dir

    rules = load_seclang_dir(RULES_DIR / "crs")
    if include_sigpack:
        rules.extend(generate_signature_rules())
    return rules
